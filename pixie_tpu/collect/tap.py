"""Live traffic tap: a TCP forward proxy that mirrors both directions of
every connection into a SocketTraceConnector event source.

Reference role: the kernel half of the socket tracer (bcc_bpf/socket_trace.c
kprobes on send/recv) captures traffic invisibly; without kernel eBPF the
userspace equivalent is an explicit tap in the traffic path. Point clients
at the tap port instead of the server and every byte is observed with
timestamps, exactly like the perf-buffer events the reference drains
(socket_trace_connector.cc TransferDataImpl).
"""
from __future__ import annotations

import socket
import threading
from typing import Optional

from pixie_tpu.collect.core import now_ns
from pixie_tpu.collect.tracer import QueueEventSource


class TapProxy:
    """Forwards 127.0.0.1:<listen_port> → <upstream>, emitting open/data/close
    events for each proxied connection."""

    def __init__(self, upstream_host: str, upstream_port: int,
                 source: Optional[QueueEventSource] = None,
                 listen_host: str = "127.0.0.1", listen_port: int = 0,
                 protocol: Optional[str] = None, pid: int = 0):
        self.upstream = (upstream_host, upstream_port)
        self.source = source or QueueEventSource()
        self.protocol = protocol
        self.pid = pid
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((listen_host, listen_port))
        self._lsock.listen(16)
        self.port = self._lsock.getsockname()[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._socks: set[socket.socket] = set()
        self._next_conn = 0
        self._lock = threading.Lock()

    def _pid_start_ns(self) -> int:
        if self.pid <= 0:
            return 0
        if not hasattr(self, "_start_ns"):
            from pixie_tpu.metadata.proc_scanner import pid_start_time_ns

            self._start_ns = pid_start_time_ns(self.pid)
        return self._start_ns

    def start(self) -> "TapProxy":
        t = threading.Thread(target=self._accept_loop, name="tap-accept",
                             daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                cli, addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._next_conn += 1
                cid = self._next_conn
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                cli.close()
                continue
            self.source.emit({
                "ev": "open", "conn": cid, "pid": self.pid,
                # real start time from /proc so the traffic's UPID matches
                # the ProcScanner-fed metadata state (ctx['pod'] joins on
                # the exact UInt128)
                "pid_start_ns": self._pid_start_ns(),
                "addr": addr[0], "port": self.upstream[1],
                # tap sits in front of the server: server-side semantics
                "role": 2, "protocol": self.protocol,
            })
            with self._lock:
                self._socks.update((cli, up))
                # prune finished pump threads so long-lived taps serving many
                # short connections don't accumulate dead Thread objects
                self._threads = [t for t in self._threads if t.is_alive()]
            for name, src, dst, direction in (
                    ("c2s", cli, up, "recv"),   # client→server = server recv
                    ("s2c", up, cli, "send")):  # server→client = server send
                t = threading.Thread(
                    target=self._pump, name=f"tap-{cid}-{name}",
                    args=(cid, src, dst, direction), daemon=True)
                t.start()
                self._threads.append(t)

    def _pump(self, cid: int, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        try:
            while not self._stop.is_set():
                try:
                    data = src.recv(65536)
                except OSError:
                    break
                if not data:
                    break
                self.source.emit({"ev": "data", "conn": cid,
                                  "dir": direction, "ts": now_ns(),
                                  "data": data})
                try:
                    dst.sendall(data)
                except OSError:
                    break
        finally:
            # Half-close propagation; the peer pump thread emits no
            # duplicate close (tracer treats repeats as idempotent).
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            if direction == "send":
                self.source.emit({"ev": "close", "conn": cid})
                with self._lock:
                    self._socks.discard(src)
                    self._socks.discard(dst)

    def stop(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        # Close per-connection sockets so pump threads blocked in recv()
        # wake immediately instead of eating the join timeout each.
        with self._lock:
            socks = list(self._socks)
            self._socks.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)

"""Socket-trace connector: byte streams → protocol records → event tables.

Reference: src/stirling/source_connectors/socket_tracer/
(socket_trace_connector.h:78 — the flagship connector; conn_tracker.h per
connection state; protocol_inference.h first-bytes protocol detection).

The kernel eBPF capture half is host-specific and unavailable here; byte
streams arrive through pluggable EventSources instead:

  * QueueEventSource  — programmatic feed (tests, in-process taps)
  * CaptureFileSource — JSONL capture replay (the reference unit-tests its
    parsers on captured byte streams the same way)
  * TapProxy (tap.py) — live TCP forward proxy emitting real traffic

Event dicts: {"ev": "open"|"data"|"close", "conn": id, "ts": ns,
"dir": "send"|"recv", "data": bytes, and on open: "pid", "addr", "port",
"role" (1=client-side, 2=server-side), "protocol" (optional hint)}.
"""
from __future__ import annotations

import base64
import collections
import json
import os
import queue
import threading
from typing import Iterable, Iterator, Optional

import numpy as np

from pixie_tpu.collect.core import SourceConnector, TableSpec, now_ns
from pixie_tpu.collect.protocols import ConnTracker, parser_registry
from pixie_tpu.collect.schemas import SCHEMAS
from pixie_tpu.types import UInt128


def infer_protocol(data: bytes, direction: str) -> Optional[str]:
    """First-bytes protocol detection (reference protocol_inference.h).

    Only protocols with unambiguous signatures are inferred; length-prefixed
    binary protocols (kafka, mux, dns-over-tcp) need an explicit hint, which
    real deployments derive from the server port.
    """
    if not data:
        return None
    b0 = data[:1]
    if data.startswith(b"PRI * HTTP/2.0"):
        return "http2"  # connection preface (RFC 7540 §3.5)
    _http_starts = (b"GET ", b"POST ", b"PUT ", b"DELETE ", b"HEAD ",
                    b"OPTIONS ", b"PATCH ", b"HTTP/1.")
    if any(data.startswith(s) for s in _http_starts):
        return "http"
    if b0 in b"*+-:$" and b"\r\n" in data[:64 * 1024]:
        return "redis"
    if data[:5] == b"INFO " or data[:8] == b"CONNECT ":
        return "nats"
    if len(data) >= 5 and data[3] == 0 and data[4] == 0x0A \
            and int.from_bytes(data[:3], "little") == len(data) - 4:
        return "mysql"  # server greeting: seq 0, protocol version 10
    if len(data) >= 9 and (data[0] & 0x7F) in (3, 4, 5) and data[4] <= 0x10 \
            and int.from_bytes(data[5:9], "big") <= 1 << 28:
        return "cql"
    if len(data) >= 8:
        code = int.from_bytes(data[4:8], "big")
        if code in (196608, 80877103):
            return "pgsql"  # startup / SSLRequest
    return None


#: reference bcc_bpf_intf/common.h traffic_protocol_t values
_PROTOCOL_IDS = {"http": 1, "http2": 2, "mysql": 3, "cql": 4, "pgsql": 5,
                 "dns": 6, "redis": 7, "nats": 8, "kafka": 10, "mux": 11}


class _Conn:
    __slots__ = ("tracker", "pending", "meta", "bytes_sent", "bytes_recv",
                 "opened", "closed_reported")

    def __init__(self, meta: dict):
        self.tracker: Optional[ConnTracker] = None
        #: (direction, data, ts) buffered until the protocol is known
        self.pending: list = []
        self.meta = meta
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.opened = True
        self.closed_reported = False


class SocketTraceConnector(SourceConnector):
    """Drains socket events from a source, parses protocols, fills the
    canonical event tables + conn_stats."""

    name = "socket_tracer"

    def __init__(self, source: "EventSource", asid: int = 0,
                 sample_period_s: float = 0.2, protocols=None,
                 name: Optional[str] = None):
        self.source = source
        self.asid = asid
        self.sample_period_s = sample_period_s
        self._parsers = parser_registry()
        if protocols is not None:
            self._parsers = {k: v for k, v in self._parsers.items()
                             if k in protocols}
        self._conns: dict = {}
        #: recently reaped conn ids — late events (half-close races from live
        #: taps) must not resurrect a connection with dataless metadata
        self._reaped: collections.OrderedDict = collections.OrderedDict()
        self.stats = {"events": 0, "records": 0, "unknown_protocol_conns": 0,
                      "parse_errors": 0, "late_events_dropped": 0}
        if name is not None:
            self.name = name

    def tables(self) -> list[TableSpec]:
        names = sorted({p.table for p in self._parsers.values()})
        names.append("conn_stats")
        return [TableSpec(n, SCHEMAS[n], sample_period_s=self.sample_period_s)
                for n in names]

    # --------------------------------------------------------------- events
    def _handle_event(self, ev: dict) -> None:
        self.stats["events"] += 1
        cid = ev.get("conn")
        kind = ev.get("ev")
        if kind == "open":
            self._conns[cid] = _Conn(meta=dict(ev))
            return
        conn = self._conns.get(cid)
        if conn is None:
            if cid in self._reaped:
                self.stats["late_events_dropped"] += 1
                return
            conn = self._conns[cid] = _Conn(meta=dict(ev))
        if kind == "close":
            if conn.tracker is not None:
                conn.tracker.closed = True
            conn.opened = False
            return
        data = ev.get("data", b"")
        if isinstance(data, str):
            data = base64.b64decode(data)
        ts = int(ev.get("ts") or now_ns())
        direction = ev.get("dir", "send")
        if direction == "send":
            conn.bytes_sent += len(data)
        else:
            conn.bytes_recv += len(data)
        if conn.tracker is None:
            proto = conn.meta.get("protocol") or infer_protocol(data, direction)
            if proto is None or proto not in self._parsers:
                conn.pending.append((direction, data, ts))
                if len(conn.pending) > 64:
                    conn.pending.clear()  # undecodable chatter: drop
                    self.stats["unknown_protocol_conns"] += 1
                return
            parser = self._parsers[proto]
            role = int(conn.meta.get("role", ConnTracker.ROLE_SERVER))
            conn.tracker = ConnTracker(
                parser, role=role,
                # UPID = (asid, pid, pid start time) — the reference resolves
                # start_time_ticks from /proc (src/shared/metadata/pids.cc);
                # capture sources supply it in the open event.
                upid=UInt128.make_upid(self.asid,
                                       int(conn.meta.get("pid", 0)),
                                       int(conn.meta.get("pid_start_ns", 0))),
                remote_addr=str(conn.meta.get("addr", "")),
                remote_port=int(conn.meta.get("port", 0)),
            )
            for d, b, t in conn.pending:
                conn.tracker.add_data(d, b, t)
            conn.pending.clear()
        conn.tracker.add_data(direction, data, ts)

    # ------------------------------------------------------------ transfers
    def transfer_data(self) -> dict[str, dict]:
        drained = self.source.drain()
        for ev in drained:
            self._handle_event(ev)
        if self.source.exhausted and not drained:
            self.exhausted = True
        rows_by_table: dict[str, list[dict]] = {}
        conn_stat_rows: list[dict] = []
        dead = []
        for cid, conn in self._conns.items():
            tr = conn.tracker
            if tr is not None:
                records = tr.process()
                self.stats["parse_errors"] += (
                    tr.req_stream.invalid_frames + tr.resp_stream.invalid_frames)
                tr.req_stream.invalid_frames = 0
                tr.resp_stream.invalid_frames = 0
                if records:
                    rows = rows_by_table.setdefault(tr.parser.table, [])
                    for rec in records:
                        row = tr.parser.record_row(rec)
                        row.setdefault("time_", now_ns())
                        row["upid"] = tr.upid
                        row["remote_addr"] = tr.remote_addr
                        row["remote_port"] = tr.remote_port
                        row["trace_role"] = tr.role
                        rows.append(row)
                    self.stats["records"] += len(records)
            if not conn.opened and not conn.closed_reported:
                conn.closed_reported = True
                conn_stat_rows.append(self._conn_stats_row(conn))
                dead.append(cid)
        for cid in dead:
            self._conns.pop(cid, None)
            self._reaped[cid] = True
        while len(self._reaped) > 4096:
            self._reaped.popitem(last=False)
        out = {}
        for table, rows in rows_by_table.items():
            out[table] = self._columnar(table, rows)
        if conn_stat_rows:
            out["conn_stats"] = self._columnar("conn_stats", conn_stat_rows)
        return out

    def _conn_stats_row(self, conn: _Conn) -> dict:
        tr = conn.tracker
        return {
            "time_": now_ns(),
            "upid": (tr.upid if tr is not None
                     else UInt128.make_upid(
                         self.asid, int(conn.meta.get("pid", 0)),
                         int(conn.meta.get("pid_start_ns", 0)))),
            "remote_addr": (tr.remote_addr if tr is not None
                            else str(conn.meta.get("addr", ""))),
            "remote_port": (tr.remote_port if tr is not None
                            else int(conn.meta.get("port", 0))),
            "trace_role": tr.role if tr is not None else 0,
            "addr_family": 2,  # AF_INET
            "protocol": _PROTOCOL_IDS.get(tr.parser.name, 0)
            if tr is not None else 0,
            "ssl": False,
            "conn_open": 1,
            "conn_close": 1,
            "conn_active": 0,
            "bytes_sent": conn.bytes_sent,
            "bytes_recv": conn.bytes_recv,
        }

    @staticmethod
    def _columnar(table: str, rows: list[dict]) -> dict:
        from pixie_tpu.types import DataType

        rel = SCHEMAS[table]
        n = len(rows)
        out: dict[str, object] = {}
        for c in rel:
            vals = [r.get(c.name) for r in rows]
            fill = "" if c.data_type == DataType.STRING else 0
            if all(v is None for v in vals):
                out[c.name] = ([""] * n if c.data_type == DataType.STRING
                               else np.zeros(n, dtype=np.int64))
            else:
                out[c.name] = [v if v is not None else fill for v in vals]
        return out


# ---------------------------------------------------------------- sources
class EventSource:
    """Supplies socket events to the tracer; drain() -> list of event dicts."""

    exhausted: bool = False

    def drain(self) -> list[dict]:
        raise NotImplementedError


class QueueEventSource(EventSource):
    """Thread-safe programmatic source (tests, in-process taps)."""

    def __init__(self):
        self._q: "queue.Queue[dict]" = queue.Queue()
        self._done = threading.Event()

    def emit(self, ev: dict) -> None:
        self._q.put(ev)

    def finish(self) -> None:
        self._done.set()

    def drain(self) -> list[dict]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                break
        if self._done.is_set() and not out:
            self.exhausted = True
        return out


class CaptureFileSource(EventSource):
    """Replays a JSONL capture file; `data` fields are base64.

    Format (one JSON object per line):
      {"ev":"open","conn":1,"pid":42,"addr":"1.2.3.4","port":3306,
       "role":2,"protocol":"mysql"}
      {"ev":"data","conn":1,"dir":"recv","ts":123,"data":"<base64>"}
      {"ev":"close","conn":1}
    """

    def __init__(self, path: str, events_per_drain: int = 4096):
        self.path = path
        self.events_per_drain = events_per_drain
        self._it: Optional[Iterator[str]] = None
        self._fh = None

    def drain(self) -> list[dict]:
        if self.exhausted:
            return []
        if self._fh is None:
            self._fh = open(self.path, "r")
        out = []
        for _ in range(self.events_per_drain):
            line = self._fh.readline()
            if not line:
                self.exhausted = True
                self._fh.close()
                break
            line = line.strip()
            if line:
                out.append(json.loads(line))
        return out


def write_capture(path: str, events: Iterable[dict]) -> int:
    """Serialize events (data as bytes) to the JSONL capture format."""
    n = 0
    with open(path, "w") as fh:
        for ev in events:
            ev = dict(ev)
            if isinstance(ev.get("data"), (bytes, bytearray)):
                ev["data"] = base64.b64encode(bytes(ev["data"])).decode()
            fh.write(json.dumps(ev) + "\n")
            n += 1
    return n

"""Access-log connector: tail web-server logs into `http_events`.

Reference role: the socket tracer's HTTP path (src/stirling/source_connectors/
socket_tracer/, http parser under protocols/http/) fills `http_events` from
kernel capture.  Kernel eBPF is host-specific; this connector provides the
same table from the ubiquitous userland source — Common/Combined Log Format
access logs (nginx/apache/envoy file output), tailed incrementally with
offset resume.

Lines parse with one compiled regex per batch; unparseable lines are counted,
not fatal (the reference's parser also drops unparseable frames).
"""
from __future__ import annotations

import os
import re
from datetime import datetime, timezone

import numpy as np

from pixie_tpu.collect.core import SourceConnector, TableSpec
from pixie_tpu.collect.schemas import SCHEMAS
from pixie_tpu.types import UInt128

#: Combined Log Format, optionally with a trailing request-time seconds field
#: (nginx `$request_time`): host ident user [time] "method path proto"
#: status bytes "referer" "ua" [rt]
_LINE_RE = re.compile(
    r'^(?P<addr>\S+) \S+ \S+ \[(?P<time>[^\]]+)\] '
    r'"(?P<method>\S+) (?P<path>\S+)(?: (?P<proto>[^"]*))?" '
    r'(?P<status>\d{3}) (?P<size>\d+|-)'
    r'(?: "(?P<referer>[^"]*)" "(?P<ua>[^"]*)")?'
    r'(?: (?P<rt>\d+(?:\.\d+)?))?\s*$'
)

_TIME_FMT = "%d/%b/%Y:%H:%M:%S %z"


def parse_line(line: str):
    """One log line → dict of http_events fields, or None if unparseable."""
    m = _LINE_RE.match(line)
    if m is None:
        return None
    try:
        t = datetime.strptime(m.group("time"), _TIME_FMT)
    except ValueError:
        return None
    size = m.group("size")
    rt = m.group("rt")
    proto = m.group("proto") or "HTTP/1.1"
    major = 2 if proto.startswith("HTTP/2") else 1
    return {
        "time_": int(t.timestamp() * 1_000_000_000),
        "remote_addr": m.group("addr"),
        "req_method": m.group("method"),
        "req_path": m.group("path"),
        "resp_status": int(m.group("status")),
        "resp_body_size": 0 if size == "-" else int(size),
        "latency": int(float(rt) * 1_000_000_000) if rt else 0,
        "major_version": major,
    }


class AccessLogConnector(SourceConnector):
    """Tails one access-log file into the canonical http_events table."""

    name = "access_log"

    def __init__(self, path: str, sample_period_s: float = 1.0,
                 asid: int = 0, follow: bool = True):
        self.path = path
        #: unique per path so several logs can feed one collector
        self.name = f"access_log:{path}"
        self.sample_period_s = sample_period_s
        self.follow = follow
        self._offset = 0
        self._partial = ""
        self._ino: int | None = None
        self._upid = UInt128.make_upid(asid, os.getpid(), 0)
        self.lines_parsed = 0
        self.lines_dropped = 0
        self.read_errors = 0

    def tables(self) -> list[TableSpec]:
        return [TableSpec("http_events", SCHEMAS["http_events"],
                          sample_period_s=self.sample_period_s)]

    def transfer_data(self) -> dict[str, dict]:
        try:
            # Rotation/truncation: a new inode (logrotate) or a size below our
            # offset (in-place truncation) restarts from the top and drops the
            # stale partial.  (A same-size in-place rewrite is undetectable
            # without content checksums — standard tail behavior.)
            st = os.stat(self.path)
            if st.st_ino != self._ino or st.st_size < self._offset:
                if self._ino is not None or st.st_size < self._offset:
                    self._offset = 0
                    self._partial = ""
                self._ino = st.st_ino
            with open(self.path, "r", errors="replace") as f:
                f.seek(self._offset)
                chunk = f.read()
                self._offset = f.tell()
        except OSError:
            # Missing path: one-shot (follow=False) connectors are done; a
            # tailing connector keeps waiting but counts the misses so a
            # typo'd path is visible in stats.
            self.read_errors += 1
            if not self.follow:
                self.exhausted = True
            return {}
        if not chunk:
            if not self.follow:
                self.exhausted = True
            return {}
        text = self._partial + chunk
        lines = text.split("\n")
        self._partial = lines.pop()  # trailing incomplete line
        rows = []
        for line in lines:
            if not line.strip():
                continue
            rec = parse_line(line)
            if rec is None:
                self.lines_dropped += 1
            else:
                rows.append(rec)
        self.lines_parsed += len(rows)
        if not rows:
            if not self.follow:
                self.exhausted = True
            return {}
        n = len(rows)
        rel = SCHEMAS["http_events"]
        out: dict[str, object] = {}
        for c in rel:
            if c.name in rows[0]:
                out[c.name] = [r[c.name] for r in rows]
            elif c.name == "upid":
                out[c.name] = [self._upid] * n
            elif c.name in ("req_headers", "resp_headers", "req_body",
                            "resp_body", "resp_message"):
                out[c.name] = [""] * n
            elif c.name == "remote_port":
                out[c.name] = np.zeros(n, dtype=np.int64)
            elif c.name == "trace_role":
                out[c.name] = np.full(n, 2, dtype=np.int64)  # responder side
            else:
                out[c.name] = np.zeros(n, dtype=np.int64)
        return {"http_events": out}

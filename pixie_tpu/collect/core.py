"""Collection runtime — the Stirling core analog.

Reference architecture (src/stirling/stirling.h:52-99, core/):
  * SourceConnector: samples a data source, appends records to DataTables
    (core/source_connector.h:65 TransferData).
  * InfoClassManager/DataTable: table schemas + RecordBuilder append
    (core/data_table.h:32-69).
  * FrequencyManager: per-source sampling/push due-times (core/frequency_manager.h).
  * Stirling::Run: poll loop over due sources, pushing into the table store
    via a registered data-push callback (stirling.cc).

TPU-native redesign: connectors produce COLUMNAR batches (dict of arrays), not
per-row records — the table store dictionary-encodes at write and seals fixed
pow2 batches, so ingest feeds the XLA engine's static shapes directly.  The
poll loop runs on a background thread while queries execute concurrently
against snapshot cursors (Table.cursor is snapshot-isolated by design).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Iterable, Optional

import numpy as np

from pixie_tpu.status import InvalidArgument
from pixie_tpu.table.table import Table, TableStore
from pixie_tpu.types import Relation


@dataclasses.dataclass(frozen=True)
class TableSpec:
    """Schema + cadence of one table a connector publishes (reference
    InfoClassManager: schema + sampling/push periods)."""

    name: str
    relation: Relation
    #: seconds between transfer_data calls for this connector
    sample_period_s: float = 1.0
    #: table store sizing
    max_bytes: int = 256 * 1024 * 1024
    batch_rows: int = 1 << 16


class SourceConnector:
    """Base class (reference core/source_connector.h).

    Lifecycle: init() once → transfer_data() on every due tick → stop().
    transfer_data returns {table_name: {col: array-like}} — empty dict or
    missing tables mean "nothing new this tick".
    """

    name: str = "source"

    def tables(self) -> list[TableSpec]:
        raise NotImplementedError

    def init(self) -> None:  # pragma: no cover - optional hook
        pass

    def transfer_data(self) -> dict[str, dict]:
        raise NotImplementedError

    def stop(self) -> None:  # pragma: no cover - optional hook
        pass

    #: True once the source is exhausted (replay reached EOF); the collector
    #: stops polling it.
    exhausted: bool = False


class FrequencyManager:
    """Earliest-due scheduling across sources (reference
    core/frequency_manager.h)."""

    def __init__(self):
        self._due: dict[str, float] = {}
        self._period: dict[str, float] = {}

    def register(self, name: str, period_s: float, now: float):
        self._period[name] = period_s
        self._due[name] = now

    def unregister(self, name: str):
        self._due.pop(name, None)
        self._period.pop(name, None)

    def due(self, now: float) -> list[str]:
        return [n for n, t in self._due.items() if t <= now]

    def mark_ran(self, name: str, now: float):
        # Schedule from the INTENDED time, not the actual run time, so load
        # does not skew the cadence (reference FrequencyManager::Sample).
        nxt = self._due[name] + self._period[name]
        if nxt <= now:  # fell behind: don't build an unbounded backlog
            nxt = now + self._period[name]
        self._due[name] = nxt

    def next_due(self) -> Optional[float]:
        return min(self._due.values()) if self._due else None


class Collector:
    """The Stirling runtime: connector registry + background poll loop pushing
    columnar batches into a TableStore (reference Stirling::Run, stirling.cc).
    """

    def __init__(self, store: Optional[TableStore] = None):
        self.store = store or TableStore()
        self._connectors: dict[str, SourceConnector] = {}
        self._freq = FrequencyManager()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.stats = {"transfers": 0, "rows_pushed": 0, "errors": 0}
        #: optional data-push callback(table_name, n_rows) — the analog of
        #: Stirling::RegisterDataPushCallback (stirling.h:52); the store write
        #: itself is built in.
        self.on_push: Optional[Callable[[str, int], None]] = None

    # ------------------------------------------------------------- registry
    def register(self, connector: SourceConnector) -> None:
        specs = connector.tables()
        if not specs:
            raise InvalidArgument(f"connector {connector.name!r} publishes no tables")
        with self._lock:  # poll thread iterates these dicts
            if connector.name in self._connectors:
                raise InvalidArgument(
                    f"connector {connector.name!r} already registered"
                )
            for spec in specs:
                if not self.store.has(spec.name):
                    self.store.create(
                        spec.name, spec.relation,
                        max_bytes=spec.max_bytes, batch_rows=spec.batch_rows,
                    )
            connector.init()
            self._connectors[connector.name] = connector
            # One cadence per connector: the fastest of its tables' periods.
            period = min(s.sample_period_s for s in specs)
            self._freq.register(connector.name, period, time.monotonic())

    def remove(self, name: str) -> None:
        with self._lock:
            self._remove_locked(name)

    def _remove_locked(self, name: str) -> None:
        c = self._connectors.pop(name, None)
        if c is not None:
            self._freq.unregister(name)
            c.stop()

    def connectors(self) -> list[str]:
        with self._lock:
            return sorted(self._connectors)

    # ------------------------------------------------------------ transfers
    def _transfer(self, name: str) -> int:
        c = self._connectors.get(name)
        if c is None:
            return 0
        try:
            out = c.transfer_data()
        except Exception:
            self.stats["errors"] += 1
            raise
        rows = 0
        for table_name, cols in (out or {}).items():
            if not cols:
                continue
            n = self.store.table(table_name).write(cols)
            rows += n
            if self.on_push is not None:
                self.on_push(table_name, n)
            if n:
                from pixie_tpu import metrics as _metrics

                _metrics.counter_inc(
                    "px_collector_rows_pushed_total", n,
                    labels={"table": table_name},
                    help_="rows pushed into the table store by connectors",
                )
        self.stats["transfers"] += 1
        self.stats["rows_pushed"] += rows
        return rows

    def transfer_once(self) -> int:
        """Run every connector once, due or not (tests / synchronous use)."""
        rows = 0
        with self._lock:
            for name in list(self._connectors):
                rows += self._transfer(name)
                self._freq.mark_ran(name, time.monotonic())
                if self._connectors[name].exhausted:
                    self._remove_locked(name)
        return rows

    # ------------------------------------------------------------ poll loop
    def _run(self):
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                for name in self._freq.due(now):
                    if self._stop.is_set():
                        break
                    try:
                        self._transfer(name)
                    except Exception:
                        pass  # connector errors must not kill the loop
                    finally:
                        # Reschedule BEFORE exhaustion-removal so an erroring
                        # connector backs off to its period instead of
                        # re-running every loop iteration.
                        if name in self._freq._due:
                            self._freq.mark_ran(name, now)
                    c = self._connectors.get(name)
                    if c is not None and c.exhausted:
                        self._remove_locked(name)
                nxt = self._freq.next_due()
            if nxt is None:
                if not self._connectors:
                    return  # all sources exhausted
                nxt = time.monotonic() + 0.1
            self._stop.wait(timeout=max(0.0, min(nxt - time.monotonic(), 0.5)))

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="pixie-collector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        for c in list(self._connectors.values()):
            c.stop()

    def wait_exhausted(self, timeout: float = 60.0) -> bool:
        """Block until every registered source is exhausted (replay use)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                if not self._connectors:
                    return True
            time.sleep(0.005)
        return False


def now_ns() -> int:
    return time.time_ns()

"""Type system.

Parity with the reference's 6 physical types and semantic-type annotations
(src/shared/types/typespb/types.proto:26-33,63-91).  The TPU twist is the *storage
class*: STRING and UINT128 columns are dictionary-encoded at ingest, so their
device representation is a dense int32 code tensor; the dictionary (unique values)
lives host-side.  All kernels therefore see only fixed-width numeric tensors.

Physical type → host (numpy) / device (jax) representation:

  BOOLEAN   bool_      bool_
  INT64     int64      int64
  UINT128   int32 code into a dictionary of (hi, lo) uint64 pairs
  FLOAT64   float64    float64 (CPU) / float32 compute policy available on TPU
  STRING    int32 code into a string dictionary
  TIME64NS  int64      int64 (nanoseconds since epoch)
"""
from __future__ import annotations

import dataclasses
import enum

import numpy as np


class DataType(enum.IntEnum):
    """Physical data types (reference types.proto:26-33)."""

    UNKNOWN = 0
    BOOLEAN = 1
    INT64 = 2
    UINT128 = 3
    FLOAT64 = 4
    STRING = 5
    TIME64NS = 6


class SemanticType(enum.IntEnum):
    """Semantic annotations (reference types.proto:63-91)."""

    ST_UNSPECIFIED = 0
    ST_NONE = 1
    ST_TIME_NS = 2
    ST_AGENT_UID = 100
    ST_ASID = 101
    ST_UPID = 200
    ST_SERVICE_NAME = 300
    ST_POD_NAME = 400
    ST_POD_PHASE = 401
    ST_POD_STATUS = 402
    ST_NODE_NAME = 500
    ST_CONTAINER_NAME = 600
    ST_CONTAINER_STATE = 601
    ST_CONTAINER_STATUS = 602
    ST_NAMESPACE_NAME = 700
    ST_BYTES = 800
    ST_PERCENT = 900
    ST_DURATION_NS = 901
    ST_THROUGHPUT_PER_NS = 902
    ST_THROUGHPUT_BYTES_PER_NS = 903
    ST_QUANTILES = 1000
    ST_DURATION_NS_QUANTILES = 1001
    ST_IP_ADDRESS = 1100
    ST_PORT = 1200
    ST_HTTP_REQ_METHOD = 1300
    ST_HTTP_RESP_STATUS = 1400
    ST_HTTP_RESP_MESSAGE = 1500
    ST_SCRIPT_REFERENCE = 3000


class PatternType(enum.IntEnum):
    """Data pattern annotations (reference types.proto PatternType)."""

    UNSPECIFIED = 0
    GENERAL = 100
    GENERAL_ENUM = 101
    STRUCTURED = 200
    METRIC_COUNTER = 300
    METRIC_GAUGE = 301


# Physical storage dtype of a column's *row* data (codes for dict-encoded types).
STORAGE_DTYPE = {
    DataType.BOOLEAN: np.dtype(np.bool_),
    DataType.INT64: np.dtype(np.int64),
    DataType.UINT128: np.dtype(np.int32),  # dictionary code
    DataType.FLOAT64: np.dtype(np.float64),
    DataType.STRING: np.dtype(np.int32),  # dictionary code
    DataType.TIME64NS: np.dtype(np.int64),
}

#: Types whose storage is a dictionary code.
DICT_ENCODED = frozenset({DataType.STRING, DataType.UINT128})

#: Types addable/comparable directly on device.
NUMERIC = frozenset({DataType.BOOLEAN, DataType.INT64, DataType.FLOAT64, DataType.TIME64NS})


def is_dict_encoded(dt: DataType) -> bool:
    return dt in DICT_ENCODED


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    data_type: DataType
    semantic_type: SemanticType = SemanticType.ST_NONE
    desc: str = ""


class Relation:
    """Ordered column schema (reference src/table_store/schema/relation.h)."""

    def __init__(self, columns: list[ColumnSchema] | None = None):
        self._cols: list[ColumnSchema] = list(columns or [])
        self._by_name = {c.name: i for i, c in enumerate(self._cols)}
        if len(self._by_name) != len(self._cols):
            raise ValueError("duplicate column names in relation")

    @staticmethod
    def of(*cols: tuple) -> "Relation":
        """Relation.of(("time_", DataType.TIME64NS), ("name", DataType.STRING, ST.ST_POD_NAME))"""
        return Relation([ColumnSchema(*c) for c in cols])

    def __len__(self) -> int:
        return len(self._cols)

    def __iter__(self):
        return iter(self._cols)

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    def __eq__(self, other) -> bool:
        return isinstance(other, Relation) and self._cols == other._cols

    def names(self) -> list[str]:
        return [c.name for c in self._cols]

    def col(self, name: str) -> ColumnSchema:
        try:
            return self._cols[self._by_name[name]]
        except KeyError:
            raise KeyError(f"column {name!r} not in relation {self.names()}") from None

    def index(self, name: str) -> int:
        return self._by_name[name]

    def dtype(self, name: str) -> DataType:
        return self.col(name).data_type

    def add(self, col: ColumnSchema) -> "Relation":
        return Relation(self._cols + [col])

    def select(self, names: list[str]) -> "Relation":
        return Relation([self.col(n) for n in names])

    def __repr__(self) -> str:
        inner = ", ".join(f"{c.name}:{c.data_type.name}" for c in self._cols)
        return f"Relation[{inner}]"

    def to_dict(self) -> list[dict]:
        return [
            {"name": c.name, "type": int(c.data_type), "st": int(c.semantic_type)}
            for c in self._cols
        ]

    @staticmethod
    def from_dict(d: list[dict]) -> "Relation":
        return Relation(
            [ColumnSchema(e["name"], DataType(e["type"]), SemanticType(e.get("st", 1))) for e in d]
        )


@dataclasses.dataclass(frozen=True, order=True)
class UInt128:
    """128-bit value as (high, low) u64 pair (reference types.proto UInt128,
    src/shared/upid/upid.h). Used for UPIDs: high = ASID<<32 | PID, low = start-time."""

    high: int
    low: int

    @staticmethod
    def make_upid(asid: int, pid: int, start_time_ns: int) -> "UInt128":
        return UInt128((asid << 32) | (pid & 0xFFFFFFFF), start_time_ns)

    @property
    def asid(self) -> int:
        return (self.high >> 32) & 0xFFFFFFFF

    @property
    def pid(self) -> int:
        return self.high & 0xFFFFFFFF

    def __str__(self) -> str:
        return f"{self.asid}:{self.pid}:{self.low}"

"""Error model.

The reference threads `Status`/`StatusOr` through every layer
(src/common/base/status.h).  In Python, exceptions are idiomatic; we keep a small
typed-exception hierarchy plus a Status value object for RPC-style boundaries
(result streams report terminal status like carnotpb's TransferResultChunk does).
"""
from __future__ import annotations

import dataclasses
import enum
import traceback


class Code(enum.IntEnum):
    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    ALREADY_EXISTS = 6
    INTERNAL = 13
    UNAVAILABLE = 14
    UNIMPLEMENTED = 12
    RESOURCE_UNAVAILABLE = 15
    COMPILER_ERROR = 100


@dataclasses.dataclass(frozen=True)
class Status:
    code: Code = Code.OK
    msg: str = ""

    @staticmethod
    def ok() -> "Status":
        return Status(Code.OK, "")

    def ok_p(self) -> bool:
        return self.code == Code.OK

    @staticmethod
    def from_exception(e: BaseException) -> "Status":
        if isinstance(e, PxError):
            return Status(e.code, str(e))
        return Status(Code.INTERNAL, "".join(traceback.format_exception_only(e)).strip())


class PxError(Exception):
    """Base error for the framework."""

    code = Code.UNKNOWN


class InvalidArgument(PxError):
    code = Code.INVALID_ARGUMENT


class NotFound(PxError):
    code = Code.NOT_FOUND


class Internal(PxError):
    code = Code.INTERNAL


class Unimplemented(PxError):
    code = Code.UNIMPLEMENTED


class Unavailable(PxError):
    """A required peer (agent/broker) is down or timed out."""

    code = Code.UNAVAILABLE


class CompilerError(PxError):
    """PxL compile error with line context (reference: planner ir::CompilerError)."""

    code = Code.COMPILER_ERROR

    def __init__(self, msg: str, line: int | None = None, col: int | None = None):
        self.line, self.col = line, col
        loc = f" (line {line})" if line is not None else ""
        super().__init__(f"{msg}{loc}")

"""Interactive live CLI: the `px live` REPL.

Reference: src/pixie_cli/pkg/live/ — an autocomplete TUI that lets the user
pick bundled scripts, edit arguments, and re-run in place.  This build is a
readline REPL over the same engine surfaces the one-shot CLI uses:
tab-completion over script names, vis variables and commands; `run`
re-executes with the current variables; `watch` re-renders on an interval
(the live loop).  The session logic lives in `LiveSession` (pure
line-in/text-out, so tests drive it without a TTY); `main_live` wires
readline + the prompt loop around it.
"""
from __future__ import annotations

import pathlib
import time
from typing import Callable, Optional

HELP = """\
commands:
  scripts [filter]      list bundled scripts
  use <script>          select a script (tab-completes)
  args                  show the selected script's variables
  set <name>=<value>    set a variable (tab-completes names)
  run [script]          execute and render widgets
  watch [seconds]       re-run every N seconds (ctrl-c stops)
  help                  this text
  quit                  exit
"""


class LiveSession:
    """State + command handling for the live loop (testable core)."""

    def __init__(self, runner: Callable, scripts_dir,
                 render: Optional[Callable] = None, max_rows: int = 15):
        """runner(source, funcs) -> (results, sink_map) — the webui runner
        contract (webui.local_runner / broker_runner)."""
        self.runner = runner
        self.scripts_dir = pathlib.Path(scripts_dir)
        self.max_rows = max_rows
        self.script: Optional[str] = None
        self.vars: dict[str, str] = {}
        self._render = render or self._default_render

    # ------------------------------------------------------------- catalog
    def script_names(self) -> list[str]:
        from pixie_tpu.scripts import bundle_map

        return sorted(bundle_map(self.scripts_dir))

    def _load(self, name: str):
        import json

        from pixie_tpu.scripts import bundle_map
        from pixie_tpu.vis import parse_vis

        d = bundle_map(self.scripts_dir).get(name)
        if d is None:
            raise FileNotFoundError(name)
        pxls = sorted(d.glob("*.pxl"))
        if not pxls:
            raise FileNotFoundError(name)
        vis_path = d / "vis.json"
        vis = parse_vis(json.loads(vis_path.read_text())) \
            if vis_path.exists() else parse_vis({})
        return pxls[0].read_text(), vis

    # ---------------------------------------------------------- completion
    def complete(self, text: str, line: str) -> list[str]:
        """Candidates for the token `text` given the whole `line` — the
        autocomplete brain (reference live view's script/arg suggester)."""
        words = line.split()
        first = words[0] if words else ""
        completing_first = len(words) <= 1 and not line.endswith(" ")
        if completing_first:
            cmds = ["scripts", "use", "args", "set", "run", "watch",
                    "help", "quit"]
            return [c for c in cmds if c.startswith(text)]
        if first in ("use", "run", "scripts"):
            return [s for s in self.script_names() if s.startswith(text)]
        if first == "set" and self.script:
            _src, vis = self._load(self.script)
            names = [v.name for v in vis.variables]
            return [f"{n}=" for n in names if n.startswith(text)]
        return []

    # ------------------------------------------------------------ commands
    def handle_line(self, line: str) -> str:
        line = line.strip()
        if not line:
            return ""
        cmd, _, rest = line.partition(" ")
        rest = rest.strip()
        if cmd in ("quit", "exit"):
            raise SystemExit(0)
        if cmd == "help":
            return HELP
        if cmd == "scripts":
            names = self.script_names()
            if rest:
                names = [n for n in names if rest in n]
            return "\n".join(names)
        if cmd == "use":
            if rest not in self.script_names():
                return f"unknown script {rest!r} (try: scripts)"
            self.script = rest
            self.vars = {}
            return self._args_text()
        if cmd == "args":
            if not self.script:
                return "no script selected (use <script>)"
            return self._args_text()
        if cmd == "set":
            if "=" not in rest:
                return "usage: set name=value"
            k, _, v = rest.partition("=")
            self.vars[k.strip()] = v.strip()
            return f"{k.strip()} = {v.strip()}"
        if cmd == "run":
            if rest:
                if rest not in self.script_names():
                    return f"unknown script {rest!r}"
                self.script = rest
            if not self.script:
                return "no script selected (use <script>)"
            return self.execute()
        if cmd == "watch":
            return "__watch__"  # the REPL loop interprets this
        return f"unknown command {cmd!r} (help for commands)"

    def _args_text(self) -> str:
        _src, vis = self._load(self.script)
        values = vis.variable_values(self.vars)
        lines = [f"script: {self.script}"]
        for v in vis.variables:
            cur = values.get(v.name, "")
            lines.append(f"  {v.name} = {cur!r}")
        return "\n".join(lines)

    def execute(self) -> str:
        source, vis = self._load(self.script)
        runs = vis.executions(self.vars)
        t0 = time.perf_counter()
        chunks = []
        if runs:
            results, sink_map = self.runner(source, list(runs))
            displays = vis.widget_displays()
            for out_name, _fn, _args in runs:
                w = displays.get(out_name)
                for _orig, fused in sink_map.get(out_name, {}).items():
                    res = results.get(fused)
                    if res is None:
                        continue
                    chunks.append(self._render(
                        out_name, w.kind if w else "Table",
                        w.display if w else {}, res))
        else:
            results, _ = self.runner(source, None)
            for sink, res in results.items():
                chunks.append(self._render(sink, "Table", {}, res))
        dt = (time.perf_counter() - t0) * 1000
        chunks.append(f"({dt:.0f} ms)")
        return "\n\n".join(chunks)

    def _default_render(self, name, kind, display, res) -> str:
        from pixie_tpu.cli import render_table
        from pixie_tpu.cli_widgets import render_widget

        hdr = f"== {name} [{kind}] ({res.num_rows} rows)"
        chart = render_widget(kind, display, res)
        body = chart if chart else render_table(res, max_rows=self.max_rows)
        return f"{hdr}\n{body}"


def main_live(runner: Callable, scripts_dir, poll_s: float = 2.0) -> int:
    """The readline prompt loop around a LiveSession."""
    import readline

    session = LiveSession(runner, scripts_dir)

    cand_cache: list = []

    def completer(text, state):
        try:
            if state == 0:
                # compute ONCE per tab press; readline calls back with
                # increasing `state` to walk the same candidate list
                cand_cache[:] = session.complete(
                    text, readline.get_line_buffer())
            return cand_cache[state] if state < len(cand_cache) else None
        except Exception:
            return None

    readline.set_completer(completer)
    readline.set_completer_delims(" \t")
    readline.parse_and_bind("tab: complete")
    print("px live — tab completes; `help` for commands")
    while True:
        try:
            line = input("px> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        try:
            out = session.handle_line(line)
        except SystemExit:
            return 0
        except Exception as e:  # surface errors, keep the loop alive
            print(f"error: {type(e).__name__}: {e}")
            continue
        if out == "__watch__":
            parts = line.split()
            try:
                interval = float(parts[1]) if len(parts) > 1 else poll_s
            except ValueError:
                print(f"usage: watch [seconds], got {parts[1]!r}")
                continue
            if not session.script:
                print("no script selected (use <script>)")
                continue
            try:
                while True:
                    print("\033[2J\033[H", end="")  # clear screen
                    print(f"[watch {session.script} every {interval}s — "
                          f"ctrl-c stops]")
                    print(session.execute())
                    time.sleep(interval)
            except KeyboardInterrupt:
                print()
                continue
            except Exception as e:  # keep the REPL alive like every command
                print(f"error: {type(e).__name__}: {e}")
                continue
        elif out:
            print(out)

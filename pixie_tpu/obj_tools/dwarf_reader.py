"""Minimal pure-Python DWARF reader: function argument locations + sizes.

Reference: src/stirling/obj_tools/dwarf_reader.cc (LLVM-based) — feeds the
dynamic tracer's "dwarvifier" pass, which turns logical probe arg captures
into physical memory reads (dynamic_tracing/dwarvifier.cc), and enriches
profiler symbolization.  This reader covers exactly what probe codegen
needs: for a named function, each formal parameter's name, byte size, and
location (frame-base offset or register), from .debug_info/.debug_abbrev
(DWARF 4 and common DWARF 5 forms).
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Optional

# ---- DWARF constants (DWARF4/5 spec) ----
DW_TAG_formal_parameter = 0x05
DW_TAG_unspecified_parameters = 0x18
DW_TAG_compile_unit = 0x11
DW_TAG_base_type = 0x24
DW_TAG_pointer_type = 0x0F
DW_TAG_typedef = 0x16
DW_TAG_const_type = 0x26
DW_TAG_volatile_type = 0x35
DW_TAG_subprogram = 0x2E

DW_AT_location = 0x02
DW_AT_name = 0x03
DW_AT_byte_size = 0x0B
DW_AT_low_pc = 0x11
DW_AT_type = 0x49
DW_AT_specification = 0x47
DW_AT_abstract_origin = 0x31
DW_AT_linkage_name = 0x6E
DW_AT_str_offsets_base = 0x72
DW_AT_frame_base = 0x40

DW_OP_call_frame_cfa = 0x9C

DW_OP_fbreg = 0x91
DW_OP_regn = 0x50  # DW_OP_reg0..reg31 = 0x50..0x6f

# forms
F_ADDR, F_BLOCK2, F_BLOCK4, F_DATA2, F_DATA4, F_DATA8 = 1, 3, 4, 5, 6, 7
F_STRING, F_BLOCK, F_BLOCK1, F_DATA1, F_FLAG, F_SDATA = 8, 9, 0xA, 0xB, 0xC, 0xD
F_STRP, F_UDATA, F_REF_ADDR, F_REF1, F_REF2, F_REF4 = 0xE, 0xF, 0x10, 0x11, 0x12, 0x13
F_REF8, F_REF_UDATA, F_INDIRECT, F_SEC_OFFSET = 0x14, 0x15, 0x16, 0x17
F_EXPRLOC, F_FLAG_PRESENT, F_STRX, F_ADDRX = 0x18, 0x19, 0x1A, 0x1B
F_REF_SUP4, F_STRP_SUP, F_DATA16, F_LINE_STRP = 0x1C, 0x1D, 0x1E, 0x1F
F_REF_SIG8, F_IMPLICIT_CONST, F_LOCLISTX, F_RNGLISTX = 0x20, 0x21, 0x22, 0x23
F_STRX1, F_STRX2, F_STRX3, F_STRX4 = 0x25, 0x26, 0x27, 0x28
F_ADDRX1, F_ADDRX2, F_ADDRX3, F_ADDRX4 = 0x29, 0x2A, 0x2B, 0x2C


def _uleb(d: bytes, off: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = d[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7


def _sleb(d: bytes, off: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = d[off]
        off += 1
        result |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            if b & 0x40:
                result -= 1 << shift
            return result, off


@dataclasses.dataclass
class ArgInfo:
    """One formal parameter of a function."""

    name: str
    byte_size: Optional[int]
    #: "fbreg<+/-N>" (frame-base relative, typical at -O0: the dwarvifier's
    #: stack-offset read) | "reg<N>" (in register) | None (no static location)
    location: Optional[str]
    type_name: str = ""


class DwarfReader:
    """Parse .debug_info for subprogram parameter names/sizes/locations."""

    def __init__(self, path: str):
        from pixie_tpu.obj_tools.elf_reader import ElfReader

        elf = ElfReader(path)
        shstr = elf._strtab(elf.e_shstrndx)
        self._secs = {}
        for s in elf._sections:
            name = ElfReader._str_at(shstr, s["name"])
            self._secs[name] = elf.data[s["offset"]: s["offset"] + s["size"]]
        self.info = self._secs.get(".debug_info", b"")
        self.abbrev = self._secs.get(".debug_abbrev", b"")
        self.str = self._secs.get(".debug_str", b"")
        self.line_str = self._secs.get(".debug_line_str", b"")
        self.str_offsets = self._secs.get(".debug_str_offsets", b"")
        if not self.info:
            raise ValueError(f"{path}: no .debug_info (compile with -g)")
        #: DIE offset (info-section-relative) -> (tag, attrs dict)
        self.dies: dict[int, tuple[int, dict]] = {}
        #: function name -> subprogram DIE offset
        self.functions: dict[str, int] = {}
        #: subprogram DIE offsets that declare `...` varargs
        self._variadic_parents: set[int] = set()
        self._parse()

    # ------------------------------------------------------------- abbrevs
    def _abbrev_table(self, off: int) -> dict[int, tuple[int, bool, list]]:
        d = self.abbrev
        out = {}
        while off < len(d):
            code, off = _uleb(d, off)
            if code == 0:
                break
            tag, off = _uleb(d, off)
            children = d[off] != 0
            off += 1
            specs = []
            while True:
                attr, off = _uleb(d, off)
                form, off = _uleb(d, off)
                if attr == 0 and form == 0:
                    break
                if form == F_IMPLICIT_CONST:
                    const, off = _sleb(d, off)
                    specs.append((attr, form, const))
                else:
                    specs.append((attr, form, None))
            out[code] = (tag, children, specs)
        return out

    # ---------------------------------------------------------------- forms
    def _read_form(self, d, off, form, cu, const):
        e = "<"
        if form == F_ADDR:
            n = cu["addr_size"]
            return int.from_bytes(d[off: off + n], "little"), off + n
        if form in (F_DATA1, F_REF1, F_STRX1, F_ADDRX1, F_FLAG):
            return d[off], off + 1
        if form in (F_DATA2, F_REF2, F_STRX2, F_ADDRX2):
            return struct.unpack_from(e + "H", d, off)[0], off + 2
        if form in (F_STRX3, F_ADDRX3):
            return int.from_bytes(d[off: off + 3], "little"), off + 3
        if form in (F_DATA4, F_REF4, F_STRX4, F_ADDRX4, F_SEC_OFFSET,
                    F_REF_ADDR, F_STRP, F_LINE_STRP, F_REF_SUP4, F_STRP_SUP):
            return struct.unpack_from(e + "I", d, off)[0], off + 4
        if form in (F_DATA8, F_REF8, F_REF_SIG8):
            return struct.unpack_from(e + "Q", d, off)[0], off + 8
        if form == F_DATA16:
            return d[off: off + 16], off + 16
        if form in (F_UDATA, F_REF_UDATA, F_STRX, F_ADDRX, F_LOCLISTX,
                    F_RNGLISTX):
            return _uleb(d, off)
        if form == F_SDATA:
            return _sleb(d, off)
        if form == F_STRING:
            end = d.index(b"\x00", off)
            return d[off:end].decode("utf-8", "replace"), end + 1
        if form == F_EXPRLOC or form == F_BLOCK:
            n, off = _uleb(d, off)
            return bytes(d[off: off + n]), off + n
        if form == F_BLOCK1:
            n = d[off]
            return bytes(d[off + 1: off + 1 + n]), off + 1 + n
        if form == F_BLOCK2:
            n = struct.unpack_from(e + "H", d, off)[0]
            return bytes(d[off + 2: off + 2 + n]), off + 2 + n
        if form == F_BLOCK4:
            n = struct.unpack_from(e + "I", d, off)[0]
            return bytes(d[off + 4: off + 4 + n]), off + 4 + n
        if form == F_FLAG_PRESENT:
            return True, off
        if form == F_IMPLICIT_CONST:
            return const, off
        if form == F_INDIRECT:
            real, off = _uleb(d, off)
            return self._read_form(d, off, real, cu, None)
        raise ValueError(f"unsupported DWARF form 0x{form:x}")

    @staticmethod
    def _cstr(tab: bytes, off: int) -> str:
        end = tab.find(b"\x00", off)
        return tab[off:end].decode("utf-8", "replace") if end >= 0 else ""

    def _strx(self, cu, idx: int) -> str:
        base = cu.get("str_off_base")
        if base is None or not self.str_offsets:
            return ""
        pos = base + 4 * idx
        if pos + 4 > len(self.str_offsets):
            return ""
        off = struct.unpack_from("<I", self.str_offsets, pos)[0]
        return self._cstr(self.str, off)

    def _attr_str(self, cu, form, val) -> str:
        if form == F_STRING:
            return val
        if form == F_STRP:
            return self._cstr(self.str, val)
        if form == F_LINE_STRP:
            return self._cstr(self.line_str, val)
        if form in (F_STRX, F_STRX1, F_STRX2, F_STRX3, F_STRX4):
            return self._strx(cu, val)
        return ""

    # ---------------------------------------------------------------- parse
    def _parse(self) -> None:
        d = self.info
        pos = 0
        while pos + 11 <= len(d):
            cu_start = pos
            (unit_len,) = struct.unpack_from("<I", d, pos)
            if unit_len == 0 or unit_len == 0xFFFFFFFF:
                break  # 64-bit DWARF unsupported; stop cleanly
            next_cu = pos + 4 + unit_len
            (version,) = struct.unpack_from("<H", d, pos + 4)
            if version >= 5:
                unit_type = d[pos + 6]
                addr_size = d[pos + 7]
                (abbrev_off,) = struct.unpack_from("<I", d, pos + 8)
                pos += 12
                if unit_type not in (0x01, 0x03):  # compile/partial unit
                    pos = next_cu
                    continue
            else:
                (abbrev_off,) = struct.unpack_from("<I", d, pos + 6)
                addr_size = d[pos + 10]
                pos += 11
            cu = {"start": cu_start, "addr_size": addr_size,
                  "str_off_base": 8 if self.str_offsets else None}
            table = self._abbrev_table(abbrev_off)
            stack = []
            while pos < next_cu:
                die_off = pos
                code, pos = _uleb(d, pos)
                if code == 0:
                    if stack:
                        stack.pop()
                    continue
                entry = table.get(code)
                if entry is None:
                    pos = next_cu
                    break
                tag, children, specs = entry
                attrs = {}
                for attr, form, const in specs:
                    val, pos = self._read_form(d, pos, form, cu, const)
                    s = self._attr_str(cu, form, val)
                    if s:
                        val = s
                    if form in (F_REF1, F_REF2, F_REF4, F_REF8, F_REF_UDATA):
                        val = cu_start + val  # CU-relative → section offset
                    attrs[attr] = val
                self.dies[die_off] = (tag, attrs)
                if tag == DW_TAG_compile_unit:
                    # per-CU str_offsets base for strx resolution (the root
                    # DIE's own strx attrs resolved with the header default,
                    # which only affects CU-name strings we don't consume)
                    base = attrs.get(DW_AT_str_offsets_base)
                    if isinstance(base, int):
                        cu["str_off_base"] = base
                if tag == DW_TAG_subprogram:
                    name = attrs.get(DW_AT_name) or attrs.get(
                        DW_AT_linkage_name)
                    if isinstance(name, str) and name:
                        self.functions.setdefault(name, die_off)
                if children:
                    stack.append(die_off)
                # record parentage for parameter attachment (and varargs
                # markers: DW_TAG_unspecified_parameters flags variadics)
                if stack and tag in (DW_TAG_formal_parameter,
                                     DW_TAG_unspecified_parameters):
                    attrs["__parent"] = stack[-1]
                    if tag == DW_TAG_unspecified_parameters:
                        self._variadic_parents.add(stack[-1])
            pos = next_cu

    # ----------------------------------------------------------------- query
    def _type_info(self, ref, depth=0) -> tuple[Optional[int], str]:
        if ref is None or depth > 16 or ref not in self.dies:
            return None, ""
        tag, attrs = self.dies[ref]
        name = attrs.get(DW_AT_name, "")
        if tag == DW_TAG_pointer_type:
            return 8, (self._type_info(attrs.get(DW_AT_type),
                                       depth + 1)[1] + "*")
        if tag in (DW_TAG_typedef, DW_TAG_const_type, DW_TAG_volatile_type):
            size, inner = self._type_info(attrs.get(DW_AT_type), depth + 1)
            return size, name if isinstance(name, str) and name else inner
        size = attrs.get(DW_AT_byte_size)
        return (int(size) if size is not None else None,
                name if isinstance(name, str) else "")

    @staticmethod
    def _decode_location(expr) -> Optional[str]:
        if not isinstance(expr, (bytes, bytearray)) or not expr:
            return None
        op = expr[0]
        if op == DW_OP_fbreg:
            off, _ = _sleb(expr, 1)
            return f"fbreg{off:+d}"
        if DW_OP_regn <= op <= DW_OP_regn + 31:
            return f"reg{op - DW_OP_regn}"
        return None

    def function_args(self, fn_name: str) -> list[ArgInfo]:
        """Formal parameters of `fn_name`, in declaration order."""
        die_off = self.functions.get(fn_name)
        if die_off is None:
            raise KeyError(f"no DWARF subprogram named {fn_name!r}")
        out = []
        for off in sorted(self.dies):
            tag, attrs = self.dies[off]
            if tag != DW_TAG_formal_parameter:
                continue
            if attrs.get("__parent") != die_off:
                continue
            size, tname = self._type_info(attrs.get(DW_AT_type))
            name = attrs.get(DW_AT_name, "")
            out.append(ArgInfo(
                name=name if isinstance(name, str) else "",
                byte_size=size,
                location=self._decode_location(attrs.get(DW_AT_location)),
                type_name=tname,
            ))
        return out

    def function_is_variadic(self, fn_name: str) -> bool:
        """True when the subprogram declares `...` varargs
        (DW_TAG_unspecified_parameters child) — O(1), recorded at parse."""
        die_off = self.functions.get(fn_name)
        if die_off is None:
            raise KeyError(f"no DWARF subprogram named {fn_name!r}")
        return die_off in self._variadic_parents

    def function_frame_base(self, fn_name: str):
        """'cfa' | 'reg<N>' | None — how fbreg offsets are anchored
        (DW_AT_frame_base).  gcc emits DW_OP_call_frame_cfa; clang -O0
        anchors on RBP (reg6), which shifts every fbreg offset — codegen
        must not assume CFA blindly."""
        die_off = self.functions.get(fn_name)
        if die_off is None:
            raise KeyError(f"no DWARF subprogram named {fn_name!r}")
        _tag, attrs = self.dies[die_off]
        expr = attrs.get(DW_AT_frame_base)
        if not isinstance(expr, (bytes, bytearray)) or not expr:
            return None
        if expr[0] == DW_OP_call_frame_cfa:
            return "cfa"
        if DW_OP_regn <= expr[0] <= DW_OP_regn + 31:
            return f"reg{expr[0] - DW_OP_regn}"
        return None

    def function_names(self) -> list[str]:
        return sorted(self.functions)

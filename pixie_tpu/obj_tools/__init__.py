"""Object-file tooling: ELF reading + address symbolization.

Reference: src/stirling/obj_tools/ (elf_reader.cc symbol iteration +
address→symbol lookup; used by the perf profiler's symbolizers and dynamic
tracing's target resolution).
"""
from pixie_tpu.obj_tools.elf_reader import (
    ElfReader,
    ElfSymbol,
    NativeSymbolizer,
)

__all__ = ["ElfReader", "ElfSymbol", "NativeSymbolizer"]

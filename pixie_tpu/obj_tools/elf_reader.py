"""Pure-Python ELF symbol reader + process-address symbolizer.

Reference: src/stirling/obj_tools/elf_reader.cc — iterate .symtab/.dynsym,
resolve addresses to function symbols (profiler symbolization), and check
symbol existence (dynamic-trace target validation).  The reference links
LLVM's object libraries; the wire format itself (ELF spec) is small enough
to parse directly, which keeps this dependency-free.

Covers ELF64 + ELF32, little/big endian, FUNC/OBJECT symbols from both
.symtab (full, when unstripped) and .dynsym (exported, always present in
shared objects), and PIE/vaddr-bias handling for live-process symbolization
via /proc/<pid>/maps.
"""
from __future__ import annotations

import bisect
import dataclasses
import struct
from typing import Optional

# e_ident offsets
_EI_CLASS = 4
_EI_DATA = 5
_ELFCLASS32, _ELFCLASS64 = 1, 2
_ELFDATA2LSB = 1

# section types
_SHT_SYMTAB = 2
_SHT_STRTAB = 3
_SHT_DYNSYM = 11

# symbol types (st_info low nibble)
STT_OBJECT = 1
STT_FUNC = 2

# program header
_PT_LOAD = 1
_PF_X = 1


@dataclasses.dataclass(frozen=True)
class ElfSymbol:
    name: str
    addr: int
    size: int
    stype: int  # STT_*

    @property
    def is_func(self) -> bool:
        return self.stype == STT_FUNC


class ElfReader:
    """Parse an ELF file's symbols (reference elf_reader.cc ElfReader)."""

    def __init__(self, path: str):
        self.path = path
        with open(path, "rb") as f:
            self.data = f.read()
        d = self.data
        if d[:4] != b"\x7fELF":
            raise ValueError(f"{path}: not an ELF file")
        self.is64 = d[_EI_CLASS] == _ELFCLASS64
        self.little = d[_EI_DATA] == _ELFDATA2LSB
        self._end = "<" if self.little else ">"
        if self.is64:
            (self.e_type, self.e_machine, _ver, self.e_entry, self.e_phoff,
             self.e_shoff, _flags, _ehsize, self.e_phentsize, self.e_phnum,
             self.e_shentsize, self.e_shnum, self.e_shstrndx) = struct.unpack(
                self._end + "HHIQQQIHHHHHH", d[16:64])
        else:
            (self.e_type, self.e_machine, _ver, self.e_entry, self.e_phoff,
             self.e_shoff, _flags, _ehsize, self.e_phentsize, self.e_phnum,
             self.e_shentsize, self.e_shnum, self.e_shstrndx) = struct.unpack(
                self._end + "HHIIIIIHHHHHH", d[16:52])
        self._sections = self._read_sections()
        self._symbols: Optional[list[ElfSymbol]] = None
        self._by_addr: Optional[tuple[list[int], list[ElfSymbol]]] = None
        self._min_load: Optional[int] = None

    # ------------------------------------------------------------- sections
    def _read_sections(self) -> list[dict]:
        d = self.data
        out = []
        fmt = (self._end + "IIQQQQIIQQ") if self.is64 else (self._end + "IIIIIIIIII")
        sz = struct.calcsize(fmt)
        for i in range(self.e_shnum):
            off = self.e_shoff + i * self.e_shentsize
            if off + sz > len(d):
                break
            (name, stype, flags, addr, offset, size, link, info, align,
             entsize) = struct.unpack(fmt, d[off: off + sz])
            out.append(dict(name=name, type=stype, addr=addr, offset=offset,
                            size=size, link=link, entsize=entsize))
        return out

    def _strtab(self, idx: int) -> bytes:
        s = self._sections[idx]
        return self.data[s["offset"]: s["offset"] + s["size"]]

    @staticmethod
    def _str_at(tab: bytes, off: int) -> str:
        end = tab.find(b"\x00", off)
        return tab[off:end].decode("utf-8", "replace") if end >= 0 else ""

    # -------------------------------------------------------------- symbols
    def symbols(self) -> list[ElfSymbol]:
        """FUNC/OBJECT symbols from .symtab + .dynsym (deduped by name+addr).
        File virtual addresses (subtract the load bias for live processes)."""
        if self._symbols is not None:
            return self._symbols
        out: dict[tuple, ElfSymbol] = {}
        sym_fmt = (self._end + "IBBHQQ") if self.is64 else (self._end + "IIIBBH")
        sym_sz = struct.calcsize(sym_fmt)
        for sec in self._sections:
            if sec["type"] not in (_SHT_SYMTAB, _SHT_DYNSYM):
                continue
            strtab = self._strtab(sec["link"])
            n = sec["size"] // max(sec["entsize"] or sym_sz, 1)
            for i in range(n):
                off = sec["offset"] + i * (sec["entsize"] or sym_sz)
                raw = self.data[off: off + sym_sz]
                if len(raw) < sym_sz:
                    break
                if self.is64:
                    name_off, info, _other, shndx, value, size = struct.unpack(
                        sym_fmt, raw)
                else:
                    name_off, value, size, info, _other, shndx = struct.unpack(
                        sym_fmt, raw)
                stype = info & 0xF
                if stype not in (STT_FUNC, STT_OBJECT) or value == 0:
                    continue
                name = self._str_at(strtab, name_off)
                if not name:
                    continue
                out[(name, value)] = ElfSymbol(name, value, size, stype)
        self._symbols = sorted(out.values(), key=lambda s: s.addr)
        return self._symbols

    def symbol(self, name: str) -> Optional[ElfSymbol]:
        for s in self.symbols():
            if s.name == name:
                return s
        return None

    def has_symbol(self, name: str) -> bool:
        return self.symbol(name) is not None

    def symbolize(self, addr: int) -> Optional[str]:
        """File-virtual address → containing function symbol name."""
        if self._by_addr is None:
            funcs = [s for s in self.symbols() if s.is_func]
            self._by_addr = ([s.addr for s in funcs], funcs)
        addrs, funcs = self._by_addr
        i = bisect.bisect_right(addrs, addr) - 1
        if i < 0:
            return None
        s = funcs[i]
        if s.size and addr >= s.addr + s.size:
            return None
        return s.name

    # ---------------------------------------------------------- load bias
    def min_load_vaddr(self) -> int:
        """Lowest PT_LOAD vaddr — the reference point for PIE bias.
        Memoized: symbolize() consults it per frame on the profiler's
        ingest path."""
        if self._min_load is not None:
            return self._min_load
        d = self.data
        fmt = (self._end + "IIQQQQQQ") if self.is64 else (self._end + "IIIIIIII")
        sz = struct.calcsize(fmt)
        lo = None
        for i in range(self.e_phnum):
            off = self.e_phoff + i * self.e_phentsize
            raw = d[off: off + sz]
            if len(raw) < sz:
                break
            if self.is64:
                ptype, _fl, _off, vaddr, _pa, _fsz, _msz, _al = struct.unpack(
                    fmt, raw)
            else:
                ptype, _off, vaddr, _pa, _fsz, _msz, _fl, _al = struct.unpack(
                    fmt, raw)
            if ptype == _PT_LOAD:
                lo = vaddr if lo is None else min(lo, vaddr)
        self._min_load = lo or 0
        return self._min_load


class NativeSymbolizer:
    """Live-process address symbolization via /proc/<pid>/maps + ElfReader.

    Reference: perf_profiler/symbolizers/ (ELF symbolization of native
    frames).  Maps a runtime address to (binary, symbol) by finding the
    containing executable mapping, loading its ELF symbols, and subtracting
    the mapping's load bias.
    """

    def __init__(self, pid: int = 0):
        import os

        self.pid = pid or os.getpid()
        #: [(start, end, file_page_offset, path)]
        self.maps: list[tuple[int, int, int, str]] = []
        self._readers: dict[str, Optional[ElfReader]] = {}
        self.reload_maps()

    def reload_maps(self) -> None:
        self.maps = []
        try:
            with open(f"/proc/{self.pid}/maps") as f:
                lines = f.read().splitlines()
        except OSError:
            return
        for line in lines:
            parts = line.split()
            if len(parts) < 6 or "x" not in parts[1]:
                continue
            path = parts[5]
            if not path.startswith("/"):
                continue
            lo, hi = (int(x, 16) for x in parts[0].split("-"))
            file_off = int(parts[2], 16)
            self.maps.append((lo, hi, file_off, path))

    def _reader(self, path: str) -> Optional[ElfReader]:
        if path not in self._readers:
            try:
                self._readers[path] = ElfReader(path)
            except (OSError, ValueError):
                self._readers[path] = None
        return self._readers[path]

    def symbolize(self, addr: int) -> str:
        """Runtime address → 'symbol (binary)' or the hex address."""
        for lo, hi, file_off, path in self.maps:
            if lo <= addr < hi:
                rd = self._reader(path)
                if rd is None:
                    break
                # runtime→file vaddr: undo the mapping bias.  The segment at
                # file offset `file_off` maps at `lo`; ELF vaddrs differ from
                # file offsets by a per-segment constant that PT_LOAD
                # alignment makes equal to (vaddr - offset) — recovered from
                # the lowest load vaddr for the common contiguous layout.
                fvaddr = addr - lo + file_off + rd.min_load_vaddr() \
                    if rd.e_type == 3 else addr  # ET_DYN (PIE/so) vs ET_EXEC
                name = rd.symbolize(fvaddr)
                if name:
                    short = path.rsplit("/", 1)[-1]
                    return f"{name} ({short})"
                break
        return hex(addr)

"""Distributed self-tracing of the query path (spans, not logs).

The engine observes everything except itself: per-op exec stats exist, but
the broker → agents → kernels → readback → merge pipeline has no end-to-end
timeline.  This module closes that loop with the system's own machinery:

  * a lightweight span API (trace_id / span_id / parent_span_id, wall-clock
    ns bounds, attributes) with a thread-safe bounded buffer per `Tracer`;
  * contextvars-based propagation inside a process and an explicit wire
    context (`wire_context()` / `root(..., ctx=...)`) across the framed-TCP
    hop between broker and agents, so every agent's spans parent under the
    broker's per-agent dispatch span;
  * finished spans land in the table store as `self_telemetry.spans` —
    the same path user data takes — so PxL queries them like any table
    (the bundled `px/self_query_latency` script), and a span→HostBatch
    adapter feeds the existing engine/otel.py resourceSpans encoder so
    traces ship to any OTLP collector.

Tracing is on by default and disabled via PL_TRACING_ENABLED=0; the disabled
fast path is a single ContextVar read per instrumentation site (no span is
ever created because no root is ever opened), which the span-hygiene ratchet
test bounds at <5% of query wall time.

Reference analogs: opentelemetry-go's span/context split, and the reference
platform's own query profiling hooks (src/carnot/exec exec stats + the
plugin OTLP export path, exec/otel_export_sink_node.*).
"""
from __future__ import annotations

import contextvars
import json
import secrets
import threading
import time
import weakref
from typing import Callable, Optional

from pixie_tpu import flags, metrics
from pixie_tpu.types import DataType as DT, Relation, SemanticType as ST

#: master switch; the disabled path never opens a root, so every child-site
#: check is one ContextVar read
flags.define_bool("PL_TRACING_ENABLED", True,
                  "record spans for the query path into self_telemetry.spans")
flags.define_int("PL_TRACE_BUFFER_SPANS", 4096,
                 "max finished spans buffered per tracer before dropping")
flags.define_str("PL_TRACE_OTLP_URL", "",
                 "when set, flushed spans also POST to this OTLP/HTTP "
                 "endpoint as resourceSpans JSON")

#: the dogfood table: every service writes its finished spans here, in its
#: own table store, so the normal distributed scan path picks them up
SPANS_TABLE = "self_telemetry.spans"
SPANS_RELATION = Relation.of(
    ("time_", DT.TIME64NS, ST.ST_TIME_NS),
    ("trace_id", DT.STRING),
    ("span_id", DT.STRING),
    ("parent_span_id", DT.STRING),
    ("name", DT.STRING),
    ("service", DT.STRING),
    ("duration_ns", DT.INT64, ST.ST_DURATION_NS),
    ("attributes", DT.STRING),
)


def enabled() -> bool:
    return bool(flags.get("PL_TRACING_ENABLED"))


class Span:
    __slots__ = ("trace_id", "span_id", "parent_span_id", "name", "service",
                 "start_ns", "end_ns", "attributes")

    def __init__(self, trace_id: str, span_id: str, parent_span_id: str,
                 name: str, service: str, start_ns: int,
                 attributes: Optional[dict] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.name = name
        self.service = service
        self.start_ns = start_ns
        self.end_ns = 0  # 0 = still open
        self.attributes = attributes if attributes is not None else {}

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    def to_row(self) -> dict:
        """JSON-safe row in the self_telemetry.spans schema (also the wire
        form the broker ships to an agent for table insertion)."""
        return {
            "time_": int(self.start_ns),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "name": self.name,
            "service": self.service,
            "duration_ns": int(self.end_ns - self.start_ns),
            "attributes": (json.dumps(self.attributes, default=str)
                           if self.attributes else ""),
        }


#: live tracers for the span-buffer health gauges (weak: a stopped service's
#: tracer must not be pinned by the metrics registry)
_LIVE: "weakref.WeakSet[Tracer]" = weakref.WeakSet()
_GAUGE_LOCK = threading.Lock()


class Tracer:
    """Per-service span factory + bounded finished-span buffer.

    Thread-safe: query threads, completion handlers, and the flush path all
    touch it concurrently.  `started == finished` after a query is the
    hygiene invariant the ratchet test enforces.
    """

    def __init__(self, service: str, max_spans: Optional[int] = None,
                 exporter: Optional[Callable[[dict], None]] = None):
        self.service = service
        self.max_spans = int(max_spans if max_spans is not None
                             else flags.get("PL_TRACE_BUFFER_SPANS"))
        self.exporter = exporter
        self._lock = threading.Lock()
        self._finished: list[Span] = []
        self.started = 0
        self.finished = 0
        self.dropped = 0
        _LIVE.add(self)

    # ------------------------------------------------------------- span api
    def start_span(self, name: str, trace_id: Optional[str] = None,
                   parent_span_id: str = "",
                   attributes: Optional[dict] = None,
                   start_ns: Optional[int] = None) -> Span:
        sp = Span(
            trace_id=trace_id or secrets.token_hex(16),
            span_id=secrets.token_hex(8),
            parent_span_id=parent_span_id,
            name=name,
            service=self.service,
            start_ns=start_ns if start_ns is not None else time.time_ns(),
            attributes=attributes,
        )
        with self._lock:
            self.started += 1
        return sp

    def finish(self, span: Span, end_ns: Optional[int] = None) -> None:
        span.end_ns = end_ns if end_ns is not None else time.time_ns()
        with self._lock:
            self.finished += 1
            if len(self._finished) >= self.max_spans:
                self.dropped += 1
            else:
                self._finished.append(span)

    def drain(self) -> list[Span]:
        with self._lock:
            out, self._finished = self._finished, []
        return out

    @property
    def open_spans(self) -> int:
        return self.started - self.finished

    @property
    def buffered(self) -> int:
        with self._lock:
            return len(self._finished)

    # ---------------------------------------------------------------- flush
    def flush(self, store=None, send: Optional[Callable[[list], None]] = None,
              ) -> list[dict]:
        """Drain finished spans; write them into `store`'s spans table and/or
        hand the row dicts to `send`; export OTLP if an exporter is set.
        Returns the drained rows (callers may forward them further)."""
        spans = self.drain()
        if not spans:
            return []
        rows = [s.to_row() for s in spans]
        if store is not None:
            write_spans(store, rows)
        if send is not None:
            send(rows)
        exporter = self.exporter
        if exporter is None:
            url = flags.get("PL_TRACE_OTLP_URL")
            if url:
                from pixie_tpu.engine.otel import http_exporter

                exporter = http_exporter({"url": url})
        if exporter is not None:
            try:
                exporter(spans_to_otlp(rows))
            except Exception:
                metrics.counter_inc(
                    "px_trace_export_errors_total",
                    help_="OTLP trace export failures (flush continues)")
        return rows


# ----------------------------------------------------------------- context

_CTX: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "pixie_tpu_trace_ctx", default=None)


def current() -> Optional[tuple]:
    """(tracer, span) of the active trace context, or None."""
    return _CTX.get()


def wire_context() -> Optional[dict]:
    """The propagation envelope carried in framed-TCP message metadata."""
    c = _CTX.get()
    if c is None:
        return None
    return {"trace_id": c[1].trace_id, "span_id": c[1].span_id}


def set_attr(**attributes) -> None:
    """Stamp attributes onto the CURRENT span (no-op without an active
    trace).  The broker uses it to mark the query root with its tenant and
    admission outcome — facts only known after the root span opened."""
    c = _CTX.get()
    if c is not None:
        c[1].attributes.update(attributes)


def start_child(name: str, **attributes) -> Optional[Span]:
    """Child span of the current context that is NOT made current — for
    spans finished on another thread (e.g. per-agent dispatch spans closed
    by the exec_done handler).  Finish with `tracer.finish(span)`."""
    c = _CTX.get()
    if c is None:
        return None
    tracer, parent = c
    return tracer.start_span(name, trace_id=parent.trace_id,
                             parent_span_id=parent.span_id,
                             attributes=attributes or None)


def event_span(name: str, start_unix_ns: int, duration_ns: int,
               **attributes) -> None:
    """Record an already-measured interval as a finished child span (the
    near-zero-cost adapter for existing exec stats / readback waves)."""
    c = _CTX.get()
    if c is None:
        return
    tracer, parent = c
    sp = tracer.start_span(name, trace_id=parent.trace_id,
                           parent_span_id=parent.span_id,
                           attributes=attributes or None,
                           start_ns=start_unix_ns)
    tracer.finish(sp, end_ns=start_unix_ns + max(0, int(duration_ns)))


class _SpanCm:
    """Context manager for a child span of the current context; a no-op
    (returns None) when no trace is active."""

    __slots__ = ("name", "attributes", "tracer", "span", "token")

    def __init__(self, name: str, attributes: Optional[dict]):
        self.name = name
        self.attributes = attributes

    def __enter__(self) -> Optional[Span]:
        c = _CTX.get()
        if c is None:
            self.span = None
            return None
        tracer, parent = c
        sp = tracer.start_span(self.name, trace_id=parent.trace_id,
                               parent_span_id=parent.span_id,
                               attributes=self.attributes)
        self.tracer = tracer
        self.span = sp
        self.token = _CTX.set((tracer, sp))
        return sp

    def __exit__(self, et, ev, tb):
        if self.span is not None:
            _CTX.reset(self.token)
            if et is not None:
                self.span.attributes["error"] = str(ev)[:200]
            self.tracer.finish(self.span)
        return False


def span(name: str, **attributes) -> _SpanCm:
    return _SpanCm(name, attributes or None)


class _RootCm:
    """Open a root span on `tracer` — a fresh trace, or a remote-parented one
    when `ctx` carries a wire context.  No-op when tracing is disabled or
    (for `only_if_idle`) a trace is already active on this thread."""

    __slots__ = ("tracer", "name", "ctx", "attributes", "span", "token",
                 "only_if_idle")

    def __init__(self, tracer: Tracer, name: str, ctx: Optional[dict],
                 attributes: Optional[dict], only_if_idle: bool):
        self.tracer = tracer
        self.name = name
        self.ctx = ctx
        self.attributes = attributes
        self.only_if_idle = only_if_idle

    def __enter__(self) -> Optional[Span]:
        self.span = None
        if not enabled():
            return None
        if self.only_if_idle and _CTX.get() is not None:
            return None
        trace_id = parent = None
        if self.ctx:
            trace_id = self.ctx.get("trace_id")
            parent = self.ctx.get("span_id")
        sp = self.tracer.start_span(self.name, trace_id=trace_id,
                                    parent_span_id=parent or "",
                                    attributes=self.attributes)
        self.span = sp
        self.token = _CTX.set((self.tracer, sp))
        return sp

    def __exit__(self, et, ev, tb):
        if self.span is not None:
            _CTX.reset(self.token)
            if et is not None:
                self.span.attributes["error"] = str(ev)[:200]
            self.tracer.finish(self.span)
        return False


def root(tracer: Tracer, name: str, ctx: Optional[dict] = None,
         **attributes) -> _RootCm:
    return _RootCm(tracer, name, ctx, attributes or None, only_if_idle=False)


def maybe_root(tracer: Tracer, name: str, **attributes) -> _RootCm:
    """Root span only when no trace is active — lets the in-process
    execute_script callers (cron, tests) get traces while the networked
    path's outer root stays the single trace root."""
    return _RootCm(tracer, name, None, attributes or None, only_if_idle=True)


def propagating_call(fn, *args, **kwargs):
    """Run fn under THIS thread's trace context — pass to thread pools whose
    workers must inherit the active span (contextvars don't cross threads)."""
    ctx = contextvars.copy_context()
    return lambda: ctx.run(fn, *args, **kwargs)


# ----------------------------------------------------------- table storage


def ensure_table(store):
    """Get-or-create the spans table in a TableStore (raced creations fold
    into the winner)."""
    if not store.has(SPANS_TABLE):
        try:
            store.create(SPANS_TABLE, SPANS_RELATION, batch_rows=1024)
        except Exception:
            pass  # lost a creation race; the table exists now
    return store.table(SPANS_TABLE)


def write_spans(store, rows: list[dict]) -> int:
    """Append span rows (Span.to_row dicts) into the store's spans table —
    the same write path user telemetry takes."""
    if not rows:
        return 0
    import numpy as np

    t = ensure_table(store)
    t.write({
        "time_": np.asarray([r["time_"] for r in rows], dtype=np.int64),
        "trace_id": [r["trace_id"] for r in rows],
        "span_id": [r["span_id"] for r in rows],
        "parent_span_id": [r["parent_span_id"] for r in rows],
        "name": [r["name"] for r in rows],
        "service": [r["service"] for r in rows],
        "duration_ns": np.asarray([r["duration_ns"] for r in rows],
                                  dtype=np.int64),
        "attributes": [r["attributes"] for r in rows],
    })
    return len(rows)


# -------------------------------------------------------------- OTLP export

#: engine/otel.py spans config for the span-row HostBatch below
OTLP_SPANS_CONFIG = {
    "resource": {"service.name": {"column": "service"},
                 "service.instance.id": {"column": "service"}},
    "spans": [{
        "name_column": "name",
        "start_time_column": "time_",
        "end_time_column": "end_time_",
        "trace_id_column": "trace_id",
        "span_id_column": "span_id",
        "parent_span_id_column": "parent_span_id",
        "attributes": [{"name": "attributes", "column": "attributes"}],
    }],
}


def spans_to_host_batch(rows: list[dict]):
    """Span rows → HostBatch in the spans schema (+ an end_time_ column),
    ready for engine.otel.batch_to_otlp / any sink that eats HostBatch."""
    import numpy as np

    from pixie_tpu.engine.executor import HostBatch
    from pixie_tpu.table.dictionary import Dictionary

    dtypes = {c.name: c.data_type for c in SPANS_RELATION}
    dtypes["end_time_"] = DT.TIME64NS
    dicts: dict = {}
    cols: dict = {}
    for name, dt in dtypes.items():
        if name == "end_time_":
            vals = [r["time_"] + r["duration_ns"] for r in rows]
        else:
            vals = [r[name] for r in rows]
        if dt == DT.STRING:
            d = Dictionary()
            cols[name] = d.encode(vals)
            dicts[name] = d
        else:
            cols[name] = np.asarray(vals, dtype=np.int64)
    return HostBatch(dtypes, dicts, cols)


def spans_to_otlp(rows: list[dict]) -> dict:
    """Span rows → OTLP/JSON resourceSpans via the existing encoder."""
    from pixie_tpu.engine.otel import batch_to_otlp

    if not rows:
        return {}
    return batch_to_otlp(spans_to_host_batch(rows), OTLP_SPANS_CONFIG)


# ------------------------------------------------------------ health gauges


def register_gauges() -> None:
    """Span-buffer health as lazy gauges (idempotent; called by broker and
    agent start).  A leaking or overflowing trace buffer is itself
    observable on /metrics.  Keyed off the metrics registry itself, so a
    metrics.reset_for_testing() followed by another service start
    re-registers instead of silently losing the gauges."""
    with _GAUGE_LOCK:
        if metrics.has_gauge_fn("px_trace_spans_started"):
            return

    def by_service(attr):
        def read():
            out: dict = {}
            for t in list(_LIVE):
                k = (("service", t.service),)
                out[k] = out.get(k, 0.0) + float(getattr(t, attr))
            return out
        return read

    metrics.register_gauge_fn("px_trace_spans_started", by_service("started"),
                              "spans started per tracer service")
    metrics.register_gauge_fn("px_trace_spans_finished",
                              by_service("finished"),
                              "spans finished per tracer service")
    metrics.register_gauge_fn("px_trace_spans_dropped", by_service("dropped"),
                              "finished spans dropped by full buffers")
    metrics.register_gauge_fn("px_trace_buffer_spans", by_service("buffered"),
                              "finished spans currently buffered (occupancy)")

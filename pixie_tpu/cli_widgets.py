"""Terminal widget renderers for vis.json display specs.

Reference: the Live UI renders vis widgets with Vega charts, request graphs,
and flamegraphs (src/api/proto/vispb/vis.proto:58-303,
src/ui/src/containers/live-widgets/) — this is the CLI-native equivalent:
braille timeseries, folded-stack flamegraphs, horizontal bar charts, and
edge lists, falling back to the aligned table for everything else.
"""
from __future__ import annotations

import numpy as np

# --------------------------------------------------------------- braille
#: braille dot bit for (x in 0..1, y in 0..3) within one cell
_DOT_BITS = ((0x01, 0x02, 0x04, 0x40), (0x08, 0x10, 0x20, 0x80))


class BrailleCanvas:
    """width×height CHARACTER canvas with 2×4 braille dots per character."""

    def __init__(self, width: int, height: int):
        self.w, self.h = width, height
        self.cells = [[0] * width for _ in range(height)]

    def dot(self, px: int, py: int) -> None:
        """Plot dot at pixel (px ∈ [0, 2w), py ∈ [0, 4h)), y=0 at BOTTOM."""
        if not (0 <= px < 2 * self.w and 0 <= py < 4 * self.h):
            return
        flipped = 4 * self.h - 1 - py
        self.cells[flipped // 4][px // 2] |= _DOT_BITS[px % 2][flipped % 4]

    def lines(self) -> list[str]:
        return ["".join(chr(0x2800 + c) for c in row) for row in self.cells]


def _fmt_val(v: float) -> str:
    a = abs(v)
    for suffix, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if a >= div:
            return f"{v / div:.4g}{suffix}"
    return f"{v:.4g}"


def render_timeseries(result, display: dict, width: int = 72,
                      height: int = 12) -> str:
    """TimeseriesChart: braille plot of value-vs-time (vispb
    TimeseriesChart: value/series/mode).  Series overlay on one canvas
    undistinguished — braille has no color — with the series count noted
    in the caption."""
    specs = display.get("timeseries") or []
    if not specs or result.num_rows == 0 or "time_" not in result.columns:
        return ""
    t = np.asarray(result.columns["time_"], dtype=np.float64)
    t0, t1 = t.min(), t.max()
    span = max(t1 - t0, 1.0)
    out = []
    for spec in specs:
        vcol = spec.get("value")
        scol = spec.get("series") or None
        if vcol not in result.columns:
            continue
        v = np.asarray(result.decoded(vcol), dtype=np.float64)
        finite = np.isfinite(v)
        if not finite.any():
            continue
        lo, hi = float(v[finite].min()), float(v[finite].max())
        vspan = max(hi - lo, 1e-12)
        canvas = BrailleCanvas(width, height)
        series_vals = ["*"]
        if scol is not None and scol in result.columns:
            series_vals = sorted(set(map(str, result.decoded(scol))))
        for i in range(len(v)):
            if not finite[i]:
                continue
            px = int((t[i] - t0) / span * (2 * width - 1))
            py = int((v[i] - lo) / vspan * (4 * height - 1))
            canvas.dot(px, py)
        ylab_hi, ylab_lo = _fmt_val(hi), _fmt_val(lo)
        pad = max(len(ylab_hi), len(ylab_lo))
        rows = canvas.lines()
        body = []
        for r, line in enumerate(rows):
            if r == 0:
                label = ylab_hi.rjust(pad)
            elif r == len(rows) - 1:
                label = ylab_lo.rjust(pad)
            else:
                label = " " * pad
            body.append(f"{label} |{line}")
        dur_s = span / 1e9
        body.append(" " * pad + " +" + "-" * width)
        body.append(" " * pad + f"  {vcol} over {dur_s:.0f}s"
                    + (f", {len(series_vals)} series ({scol})"
                       if scol else ""))
        out.append("\n".join(body))
    return "\n".join(out)


def render_flamegraph(result, display: dict, width: int = 96,
                      max_depth: int = 30, min_pct: float = 0.5) -> str:
    """StackTraceFlameGraph: folded stacks ('a;b;c' + count) → tree with
    width-scaled bars and cumulative percentages."""
    scol = display.get("stacktraceColumn", "stack_trace")
    ccol = display.get("countColumn", "count")
    if scol not in result.columns or result.num_rows == 0:
        return ""
    stacks = result.decoded(scol)
    counts = np.asarray(result.decoded(ccol), dtype=np.float64) \
        if ccol in result.columns else np.ones(len(stacks))

    root: dict = {"n": 0.0, "kids": {}}
    for stack, c in zip(stacks, counts):
        node = root
        node["n"] += c
        for frame in str(stack).split(";"):
            frame = frame.strip()
            if not frame:
                continue
            node = node["kids"].setdefault(frame, {"n": 0.0, "kids": {}})
            node["n"] += c
    total = root["n"] or 1.0

    lines = [f"flamegraph: {int(total)} samples"]

    def walk(node, depth):
        if depth > max_depth:
            return
        kids = sorted(node["kids"].items(), key=lambda kv: -kv[1]["n"])
        for name, k in kids:
            pct = 100.0 * k["n"] / total
            if pct < min_pct:
                continue
            bar_w = max(1, int(pct / 100.0 * (width - 2 * depth)))
            label = f"{name} {pct:.1f}%"
            bar = "█" * min(bar_w, max(width - 2 * depth, 4))
            lines.append("  " * depth + f"{bar} {label}")
            walk(k, depth + 1)

    walk(root, 0)
    return "\n".join(lines)


def render_bars(result, display: dict, width: int = 60,
                max_rows: int = 24) -> str:
    """BarChart / HistogramChart: horizontal bars (vispb BarChart bar.value/
    bar.label; HistogramChart histogram.value with label falling back to the
    first string column)."""
    bar = display.get("bar") or {}
    vcol, lcol = bar.get("value"), bar.get("label")
    if not vcol:
        hist = display.get("histogram") or {}
        vcol = hist.get("value")
        lcol = next((c for c in result.relation.names()
                     if c in result.dictionaries), None)
    if not vcol or vcol not in result.columns or result.num_rows == 0:
        return ""
    v = np.asarray(result.decoded(vcol), dtype=np.float64)
    labels = ([str(x) for x in result.decoded(lcol)]
              if lcol and lcol in result.columns
              else [str(i) for i in range(len(v))])
    order = np.argsort(-v)[:max_rows]
    vmax = max(float(v[order[0]]), 1e-12) if len(order) else 1.0
    pad = max((len(labels[i]) for i in order), default=0)
    lines = []
    for i in order:
        w = max(1, int(v[i] / vmax * width)) if v[i] > 0 else 0
        lines.append(f"{labels[i].rjust(pad)} |{'█' * w} {_fmt_val(float(v[i]))}")
    return "\n".join(lines)


def render_graph(result, display: dict, max_edges: int = 40) -> str:
    """Graph / RequestGraph: edge list with optional edge metrics."""
    g = display.get("requestGraph") or display.get("graph") or {}
    src = (g.get("requestorPodColumn") or g.get("requestorServiceColumn")
           or g.get("fromColumn"))
    dst = (g.get("responderPodColumn") or g.get("responderServiceColumn")
           or g.get("toColumn"))
    if not src or not dst or src not in result.columns \
            or dst not in result.columns:
        # guess the first two string columns
        strcols = [c for c in result.relation.names()
                   if c in result.dictionaries]
        if len(strcols) < 2 or result.num_rows == 0:
            return ""
        src, dst = strcols[0], strcols[1]
    a = [str(x) for x in result.decoded(src)]
    b = [str(x) for x in result.decoded(dst)]
    metric = next((c for c in result.relation.names()
                   if c not in (src, dst, "time_")
                   and np.issubdtype(np.asarray(result.columns[c]).dtype,
                                     np.number)
                   and c not in result.dictionaries), None)
    m = result.decoded(metric) if metric else None
    pad = max((len(x) for x in a), default=0)
    lines = []
    for i in range(min(len(a), max_edges)):
        extra = f"  [{metric}={_fmt_val(float(m[i]))}]" if m is not None else ""
        lines.append(f"{a[i].rjust(pad)} ──▶ {b[i]}{extra}")
    if len(a) > max_edges:
        lines.append(f"... ({len(a) - max_edges} more edges)")
    return "\n".join(lines)


#: widget kind → renderer (None = fall back to the plain table)
RENDERERS = {
    "TimeseriesChart": render_timeseries,
    "StackTraceFlameGraph": render_flamegraph,
    "BarChart": render_bars,
    "HistogramChart": render_bars,
    "RequestGraph": render_graph,
    "Graph": render_graph,
}


def render_widget(kind: str, display: dict, result) -> str:
    """'' when no renderer applies (caller falls back to the table)."""
    fn = RENDERERS.get(kind)
    if fn is None:
        return ""
    try:
        return fn(result, display)
    except Exception:
        return ""  # a rendering bug must never hide the data: show the table

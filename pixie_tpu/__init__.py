"""pixie_tpu: a TPU-native telemetry-analytics framework with the capabilities of Pixie.

Architecture (see ARCHITECTURE.md): telemetry enters an in-memory columnar table store
where variable-width values (strings, 128-bit UPIDs) are dictionary-encoded to dense
int32 codes at ingest.  PxL queries compile through an IR into plan fragments; each
fragment is lowered to a single fused `jax.jit` function over fixed-shape padded
columnar tensors and executed on TPU.  Distribution is SPMD: the same fragment runs
over a `jax.sharding.Mesh` with partial aggregates merged by XLA collectives (psum)
instead of the reference's per-node C++ exec + gRPC result streams.

Reference parity map: /root/reference (easyops-cn/pixie), see SURVEY.md.
"""
import jax as _jax

# Timestamps are int64 nanoseconds (TIME64NS, reference src/shared/types/typespb/
# types.proto:26-33); the engine therefore requires 64-bit mode globally.
_jax.config.update("jax_enable_x64", True)

from pixie_tpu.types import DataType, SemanticType, Relation  # noqa: E402,F401
from pixie_tpu.table import Table, TableStore, RowBatch  # noqa: E402,F401
import pixie_tpu.metadata  # noqa: E402,F401  (registers metadata UDFs)

__version__ = "0.1.0"
